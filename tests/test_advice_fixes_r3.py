"""Regression tests for the round-3 advisor findings (ADVICE.md r3):

1. training entry points adopting a converted-Mixtral checkpoint config
   (moe_capacity_factor = E/top_k, dropless) got no warning about the
   O(seq^2) dispatch buffers.
2. expert_axis() was re-derived from the global mesh independently at
   param-spec time and trace time; a mesh re-init in between silently
   disagreed.
3. nesting_mesh() fell back to the concrete global mesh when an abstract
   (shard_map) mesh was active but lacked the axis — nesting over a
   different mesh than the enclosing context fails with an opaque error.
4. ZeRO-1 state_specs consulted DEFAULT_RULES, not the rules the params
   were actually sharded with.
5. place_host_batch's multi-host branch never verified the byte-identical
   global batch contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu import topology
from megatron_llm_tpu.config import TransformerConfig


def _moe_cfg(**kw):
    base = dict(
        num_layers=2, hidden_size=32, num_attention_heads=4,
        ffn_hidden_size=64, num_experts=4, moe_top_k=2,
        glu_activation="swiglu", add_bias_linear=False,
        normalization="rmsnorm",
        position_embedding_type="rotary", tie_embed_logits=False,
        padded_vocab_size=64, seq_length=16, max_position_embeddings=16,
    )
    base.update(kw)
    return TransformerConfig(**base)


# ---------------------------------------------------------------------------
# 1. dropless capacity factor warns at validate_args time
# ---------------------------------------------------------------------------

def test_validate_args_warns_on_dropless_capacity(capsys):
    from megatron_llm_tpu.arguments import parse_args, validate_args

    args = parse_args(args_list=[
        "--num_layers", "2", "--hidden_size", "32",
        "--num_attention_heads", "4", "--micro_batch_size", "1",
        "--seq_length", "16", "--max_position_embeddings", "16",
        "--num_experts", "8", "--moe_top_k", "2",
        "--moe_capacity_factor", "4.0",   # == E/top_k -> dropless
    ])
    validate_args(args, world_size=1)
    out = capsys.readouterr().out
    assert "DROPLESS" in out and "moe_capacity_factor" in out

    args = parse_args(args_list=[
        "--num_layers", "2", "--hidden_size", "32",
        "--num_attention_heads", "4", "--micro_batch_size", "1",
        "--seq_length", "16", "--max_position_embeddings", "16",
        "--num_experts", "8", "--moe_top_k", "2",
        "--moe_capacity_factor", "1.25",  # training default: quiet
    ])
    validate_args(args, world_size=1)
    assert "DROPLESS" not in capsys.readouterr().out


# ---------------------------------------------------------------------------
# 2. expert axis resolved once at model construction, not per call
# ---------------------------------------------------------------------------

def test_expert_axis_pinned_at_model_construction(utils):
    from megatron_llm_tpu.models.llama import LlamaModel
    from megatron_llm_tpu.models.moe import moe_mlp_specs

    # dp = 4 divides E = 4: resolution says 'expert'
    utils.initialize_model_parallel(tp=2)  # dp = 8/2 = 4
    model = LlamaModel(_moe_cfg())
    assert model.cfg.moe_expert_axis == "expert"
    params = model.init(jax.random.PRNGKey(0))
    spec_before = moe_mlp_specs(
        params["transformer"]["layers"]["mlp"], cfg=model.cfg)

    # adversarial mesh change AFTER construction: dp = 8 does not divide
    # E = 4 -> live derivation would now say replicated, silently
    # disagreeing with the already-placed params
    utils.initialize_model_parallel(tp=1)  # dp = 8
    spec_after = moe_mlp_specs(
        params["transformer"]["layers"]["mlp"], cfg=model.cfg)
    assert spec_after == spec_before
    assert spec_after["experts"]["w_in"][1] == "expert"

    # a model built under the new mesh resolves fresh
    model2 = LlamaModel(_moe_cfg())
    assert model2.cfg.moe_expert_axis == "replicated"


def test_expert_axis_stays_auto_without_mesh(utils):
    """A model constructed BEFORE initialize_model_parallel must not pin
    'replicated' (which would permanently disable EP); it stays 'auto'
    and resolves live once a mesh exists."""
    from megatron_llm_tpu.models.llama import LlamaModel
    from megatron_llm_tpu.models.moe import moe_mlp_specs

    utils.destroy_model_parallel()
    model = LlamaModel(_moe_cfg())
    assert model.cfg.moe_expert_axis == "auto"

    utils.initialize_model_parallel(tp=2)  # dp = 4 divides E = 4
    params = model.init(jax.random.PRNGKey(0))
    spec = moe_mlp_specs(
        params["transformer"]["layers"]["mlp"], cfg=model.cfg)
    assert spec["experts"]["w_in"][1] == "expert"


def test_moe_mlp_uses_cfg_resolution_not_live_mesh(utils):
    """Forward under a pinned 'replicated' config must not emit 'expert'
    constraints even when the live mesh would allow them."""
    from megatron_llm_tpu.models.moe import init_moe_mlp_params, moe_mlp

    cfg = _moe_cfg(moe_expert_axis="replicated", moe_capacity_factor=8.0)
    utils.initialize_model_parallel(tp=2)  # dp=4 divides E=4 ('expert' live)
    p = init_moe_mlp_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32))
    out, aux = jax.jit(lambda x, p: moe_mlp(x, p, cfg))(x, p)
    assert out.shape == x.shape and np.isfinite(np.asarray(out)).all()


# ---------------------------------------------------------------------------
# 3. nesting_mesh: abstract mesh lacking the axis -> clean (None, None)
# ---------------------------------------------------------------------------

def test_nesting_mesh_no_silent_global_fallback(utils):
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5 keeps it in experimental
        from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    utils.initialize_model_parallel(tp=2)  # global mesh HAS a tp axis
    seen = {}

    sub = Mesh(np.array(jax.devices()[:2]), ("x",))

    def inner(a):
        seen["result"] = topology.nesting_mesh("tp")
        return a

    jax.jit(shard_map(
        inner, mesh=sub, in_specs=P("x"), out_specs=P("x"),
    ))(jnp.arange(8.0))
    # inside a shard_map over a mesh WITHOUT 'tp', the old code returned
    # the concrete global mesh (which has tp=2) — a nesting error waiting
    # to happen; now it must route callers to their fallback path
    assert seen["result"] == (None, None)

    # outside any mesh context the concrete global mesh still governs
    mesh, manual = topology.nesting_mesh("tp")
    assert mesh is not None and "tp" in mesh.axis_names


# ---------------------------------------------------------------------------
# 4. ZeRO-1 state_specs honors the active rules table
# ---------------------------------------------------------------------------

def test_zero1_state_specs_respect_custom_rules(utils):
    from megatron_llm_tpu.config import TrainConfig
    from megatron_llm_tpu.optimizer import MegatronOptimizer

    utils.initialize_model_parallel(tp=1)  # dp = 8
    tc = TrainConfig(micro_batch_size=1, global_batch_size=8,
                     train_iters=0, lr=1e-4, optimizer="adam", bf16=True)
    opt = MegatronOptimizer(tc, params_dtype=jnp.bfloat16)
    params = {"w": jnp.zeros((16, 8), jnp.bfloat16)}
    specs = {"w": ("ffn", None)}

    # DEFAULT_RULES maps 'ffn' -> tp: the leaf is NOT on dp, so ZeRO-1
    # adds a dp_shard axis on the free dim
    s_default = opt.state_specs(specs, params, zero1=True, dp_size=8)
    assert s_default.exp_avg["w"] == ("ffn", "dp_shard")

    # custom table shards 'ffn' over dp: the leaf's state memory is
    # already divided by dp; a second dp axis must NOT be added
    custom = {"ffn": topology.DP_AXIS, None: None}
    s_custom = opt.state_specs(specs, params, zero1=True, dp_size=8,
                               rules=custom)
    assert s_custom.exp_avg["w"] == ("ffn", None)


# ---------------------------------------------------------------------------
# 5. multi-host batch checksum catches divergence
# ---------------------------------------------------------------------------

def test_cross_host_batch_checksum(monkeypatch):
    from megatron_llm_tpu.data import data_samplers as ds

    calls = {}

    def fake_allgather(x):
        calls["hash"] = int(x)
        return np.array(calls["returns"], np.uint32)

    monkeypatch.setattr(
        "jax.experimental.multihost_utils.process_allgather",
        fake_allgather)

    arr = np.arange(12, dtype=np.int32).reshape(3, 4)
    import zlib
    h = zlib.crc32(arr.tobytes()) & 0xFFFFFFFF

    calls["returns"] = [h, h, h, h]          # all hosts agree -> silent
    ds._verify_cross_host_batch(arr)

    calls["returns"] = [h, h ^ 1, h, h]      # one host diverges -> raise
    with pytest.raises(RuntimeError, match="DIVERGE"):
        ds._verify_cross_host_batch(arr)
