#!/usr/bin/env python
"""BERT pretraining entry point (masked-LM + sentence-order prediction).

Reference: ``/root/reference/pretrain_bert.py`` — builds BertModel, batches
with (tokens, loss_mask, lm_labels, padding_mask, tokentype_ids,
sentence_order), and a loss_func summing the masked LM loss with the binary
SOP cross entropy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu import checkpointing, topology
from megatron_llm_tpu.data.data_samplers import place_host_batch
from megatron_llm_tpu.arguments import (
    parallel_config_from_args,
    train_config_from_args,
    transformer_config_from_args,
)
from megatron_llm_tpu.initialize import initialize_megatron
from megatron_llm_tpu.models.bert import (
    BERT_ARCH_FLAGS,
    BertModel,
    bert_config,
)
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.training import pretrain
from jax.sharding import NamedSharding, PartitionSpec as P


def extra_args(parser):
    g = parser.add_argument_group("bert")
    g.add_argument("--bert_no_binary_head", action="store_true",
                   help="disable the sentence-order binary head")
    g.add_argument("--masked_lm_prob", "--mask_prob",
                   dest="masked_lm_prob", type=float, default=0.15)
    g.add_argument("--short_seq_prob", type=float, default=0.1)
    return parser


def bert_loss_func(model_out, loss_mask):
    """lm + sop loss, logged separately (reference: pretrain_bert.py
    loss_func returns {'lm loss', 'sop loss'})."""
    lm_loss_tok, sop_loss = model_out
    loss_mask = loss_mask.astype(jnp.float32)
    lm = jnp.sum(lm_loss_tok * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1.0)
    if sop_loss is None:
        return lm
    sop = jnp.mean(sop_loss)
    return lm + sop, {"lm loss": lm, "sop loss": sop}


def build_data_iterator(args, mesh, num_micro):
    dsh = NamedSharding(mesh, P(None, "dp", None))
    mb = args.micro_batch_size * args.data_parallel_size

    if args.data_path is None:
        rng = np.random.RandomState(args.seed)

        def synth():
            while True:
                toks = rng.randint(
                    0, args.padded_vocab_size, (num_micro, mb, args.seq_length)
                ).astype(np.int32)
                yield {
                    "tokens": toks,
                    "labels": toks,
                    "loss_mask": (rng.rand(*toks.shape) < args.masked_lm_prob
                                  ).astype(np.float32),
                    "attention_mask": np.ones_like(toks),
                    "tokentype_ids": np.zeros_like(toks),
                    "sentence_order": rng.randint(
                        0, 2, (num_micro, mb)).astype(np.int32),
                }
        host_iter = synth()
    else:
        from megatron_llm_tpu.data.bert_dataset import (
            bert_collate,
            build_train_valid_test_datasets,
        )
        from megatron_llm_tpu.data.data_samplers import (
            build_pretraining_data_loader,
        )

        n_train = args.train_iters * args.global_batch_size
        train_ds, _, _ = build_train_valid_test_datasets(
            args.data_path, args.split, [n_train, 0, 0],
            max_seq_length=args.seq_length,
            masked_lm_prob=args.masked_lm_prob,
            short_seq_prob=args.short_seq_prob,
            seed=args.seed,
            binary_head=not args.bert_no_binary_head,
        )
        host_iter = iter(build_pretraining_data_loader(
            train_ds, 0, args.micro_batch_size, args.data_parallel_size,
            num_micro, args.dataloader_type, args.seed,
            collate_fn=bert_collate,
        ))

    def gen():
        for b in host_iter:
            out = {}
            for k, v in b.items():
                arr = np.asarray(v)
                s = (P(None, "dp") if arr.ndim == 2
                     else P(None, "dp", None))
                out[k] = place_host_batch(arr, NamedSharding(mesh, s))
            yield out

    return gen()


def main():
    args = initialize_megatron(extra_args_provider=extra_args)
    if args.padded_vocab_size is None:
        raise SystemExit("need --vocab_size/--padded_vocab_size or a tokenizer")
    if args.pipeline_model_parallel_size > 1:
        # the BERT path runs through the generic (non-pipelined) train step;
        # use finetune.py / pretrain_gpt.py for pp > 1
        raise SystemExit(
            "pretrain_bert.py does not support "
            "--pipeline_model_parallel_size > 1 (tp/dp only)"
        )

    mesh = topology.get_mesh()
    base = transformer_config_from_args(args, "gpt")
    cfg = bert_config(**{
        f.name: getattr(base, f.name)
        for f in base.__dataclass_fields__.values()
        if f.name not in BERT_ARCH_FLAGS
    })
    model = BertModel(cfg, add_binary_head=not args.bert_no_binary_head)
    tc = train_config_from_args(args)
    pc = parallel_config_from_args(args)
    num_micro = args.global_batch_size // (
        args.micro_batch_size * args.data_parallel_size
    )

    params = None
    start_iteration = 0
    opt_state = None
    if args.load:
        params, opt_state, meta = checkpointing.load_checkpoint(
            args.load, finetune=args.finetune,
            iteration=getattr(args, "load_iters", None),
        )
        if params is not None:
            start_iteration = meta["iteration"]
    if params is None:
        params = model.init(jax.random.PRNGKey(args.seed))
    params = sh.shard_params(params, model.param_specs(params))
    if args.fp16 or args.bf16:
        dt = jnp.float16 if args.fp16 else jnp.bfloat16
        params = jax.tree_util.tree_map(lambda p: p.astype(dt), params)

    train_iter = build_data_iterator(args, mesh, num_micro)
    if getattr(args, "eval_only", False):
        # reference --eval_only: forward-only pass over the data, no update
        from megatron_llm_tpu.optimizer import MegatronOptimizer
        from megatron_llm_tpu.training import build_train_step

        opt = MegatronOptimizer(
            tc, params_dtype=jax.tree_util.tree_leaves(params)[0].dtype)
        step = build_train_step(model, opt, pc, num_micro, bert_loss_func,
                                forward_only=True)
        losses = [float(step(params, next(train_iter), None))
                  for _ in range(args.eval_iters)]
        print(f" eval_only: loss {sum(losses) / len(losses):.6E} over "
              f"{len(losses)} batches")
        return

    params, opt_state, it = pretrain(
        model, params, tc, pc, train_iter,
        loss_func=bert_loss_func,
        log_interval=args.log_interval,
        save_interval=args.save_interval,
        save_dir=args.save,
        start_iteration=start_iteration,
        opt_state=opt_state,
    )
    if args.save:
        checkpointing.save_checkpoint(args.save, it, params, opt_state)


if __name__ == "__main__":
    main()
